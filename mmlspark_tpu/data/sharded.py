"""Sharded/out-of-core dataset ingest — the scale-out data path.

BASELINE config 3 (Higgs-1B on v5e-16) cannot hold the raw float matrix in
one host's RAM: 1B x 28 float64 is ~224 GB. The design point that makes it
addressable is that GBDT training consumes *binned uint8* features (8x
smaller; 28 GB for Higgs-1B — 1.75 GB/chip HBM over 16 chips), and binning
is a streaming operation:

1. pass 1 streams a bounded per-shard sample to fit the quantile
   :class:`BinMapper` (the ``bin_construct_sample_cnt`` pass);
2. pass 2 streams each shard through ``apply_bins`` into an on-disk uint8
   memmap (the float data never co-resides);
3. training device_puts the memmap directly — uint8 arrays skip the copy
   in ``train()`` and stream from disk to HBM.

Shard files are ``.npz`` (keys ``X``/``y``/optional ``w``) or ``.npy``
(features only); parquet loads through pandas when an engine is installed.
The per-shard layout maps onto mesh data slices via
``parallel.mesh.partition_assignment`` — each executor host binning its own
shards is the multi-host version of this module (SURVEY.md §7 step 3's
host-side ingest role).

Every written shard carries a ``<shard>.crc32`` sidecar; loads verify it
when present and a mismatch raises
:class:`~mmlspark_tpu.runtime.lineage.PartitionLostError` — under the
fault-tolerant scheduler that routes the shard through
``Lineage.recompute`` (a fresh read of the source file), so a torn or
bit-rotted read is retried instead of silently binning garbage.

Corrupt-record read modes (Spark's ``mode`` option — dataguard):
``ShardedDataset(paths, mode="permissive", bad_records_path=...)``
quarantines torn/CRC-mismatched/undecodable shards to a dead-letter
store and streams the survivors in path order (deterministic: a fit
over the corrupted input is byte-identical to a fit over the clean
complement); ``dropmalformed`` drops and counts; ``failfast`` (default)
keeps the raise-on-first-corruption behavior above.
``ignore_corrupt_files=True`` is the
``spark.sql.files.ignoreCorruptFiles`` analogue — file-level corruption
is skipped even under ``failfast``.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import zipfile
import zlib
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.dataguard.modes import (
    FAILFAST,
    PERMISSIVE,
    BadRecordsError,
    CorruptRecord,
    normalize_mode,
)
from mmlspark_tpu.lightgbm.binning import BinMapper, apply_bins, fit_bin_mapper
from mmlspark_tpu.runtime.faults import CorruptShardError, check_record
from mmlspark_tpu.runtime.lineage import PartitionLostError

#: error classes a corrupt shard file can surface as at decode time
_CORRUPT_ERRORS = (
    CorruptShardError,
    PartitionLostError,
    zipfile.BadZipFile,
    ValueError,
    KeyError,
    OSError,
)


def _file_crc32(path: str) -> int:
    """Streaming CRC32 of a file's bytes (shards can be GB-scale)."""
    crc = 0
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def write_shard_sidecar(path: str) -> str:
    """Write ``<path>.crc32`` holding the hex CRC32 of the shard bytes;
    returns the sidecar path. Loads verify it when it exists."""
    sidecar = path + ".crc32"
    crc = _file_crc32(path)
    with open(sidecar, "w", encoding="utf-8") as fh:
        fh.write(f"{crc:08x}")
    return sidecar


def _verify_shard(path: str) -> None:
    """Check ``path`` against its ``.crc32`` sidecar (no-op when absent).
    A mismatch raises PartitionLostError so the scheduler's lineage path
    re-reads the shard instead of consuming corrupt bytes."""
    sidecar = path + ".crc32"
    try:
        with open(sidecar, "r", encoding="utf-8") as fh:
            want = fh.read().strip()
    except OSError:
        return
    got = f"{_file_crc32(path):08x}"
    if got != want:
        raise PartitionLostError(
            f"shard {path} failed CRC verification "
            f"(sidecar {want}, file {got})"
        )


@dataclasses.dataclass
class ShardInfo:
    path: str
    num_rows: int
    num_features: int
    has_y: bool = False
    has_w: bool = False


def _npy_header_shape(fh) -> Tuple[int, ...]:
    version = np.lib.format.read_magic(fh)
    if version == (1, 0):
        shape, _, _ = np.lib.format.read_array_header_1_0(fh)
    else:
        shape, _, _ = np.lib.format.read_array_header_2_0(fh)
    return shape


class ShardedDataset:
    """Lazy view over shard files; at most one shard's float data is
    resident at a time.

    ``mode`` is Spark's corrupt-record option (``permissive`` /
    ``dropmalformed`` / ``failfast``, case-insensitive). Under the
    non-failfast modes the scan pass verifies every shard *eagerly*
    (fault gate, CRC sidecar, header decode) so the corrupt set is
    known before anything is sized over the survivors — row offsets,
    samples, and memmap extents all see the same deterministic
    survivor list. ``bad_records_path`` dead-letters the quarantined
    shards (``permissive`` only); ``ignore_corrupt_files`` skips
    corrupt files even under ``failfast``, like
    ``spark.sql.files.ignoreCorruptFiles``.
    """

    def __init__(
        self,
        shards: Sequence[str],
        mode: str = FAILFAST,
        bad_records_path: Optional[str] = None,
        ignore_corrupt_files: bool = False,
    ):
        if not shards:
            raise ValueError("no shard files given")
        self.paths = list(shards)
        self.mode = normalize_mode(mode)
        if ignore_corrupt_files and self.mode == FAILFAST:
            # ignoreCorruptFiles is file-level tolerance regardless of
            # mode; a whole-shard quarantine IS the file level here
            self.mode = "dropmalformed"
        self.bad_records_path = bad_records_path
        #: CorruptRecords quarantined by the eager scan (non-failfast)
        self.quarantined: List[CorruptRecord] = []
        self._infos: Optional[List[ShardInfo]] = None
        self._num_features: Optional[int] = None

    # -- construction --------------------------------------------------------

    @staticmethod
    def write_shards(
        out_dir: str,
        X: np.ndarray,
        y: Optional[np.ndarray] = None,
        w: Optional[np.ndarray] = None,
        rows_per_shard: int = 100_000,
    ) -> "ShardedDataset":
        """Test/demo helper: split an in-memory matrix into .npz shards."""
        os.makedirs(out_dir, exist_ok=True)
        paths = []
        n = len(X)
        for si, lo in enumerate(range(0, n, rows_per_shard)):
            hi = min(lo + rows_per_shard, n)
            path = os.path.join(out_dir, f"shard_{si:05d}.npz")
            payload = {"X": np.asarray(X[lo:hi])}
            if y is not None:
                payload["y"] = np.asarray(y[lo:hi])
            if w is not None:
                payload["w"] = np.asarray(w[lo:hi])
            np.savez(path, **payload)
            write_shard_sidecar(path)
            paths.append(path)
        return ShardedDataset(paths)

    # -- shard access --------------------------------------------------------

    @staticmethod
    def _load(path: str) -> Tuple[np.ndarray, Optional[np.ndarray], Optional[np.ndarray]]:
        check_record(path)
        _verify_shard(path)
        if path.endswith(".npz"):
            with np.load(path, allow_pickle=False) as z:
                X = np.asarray(z["X"], dtype=np.float64)
                y = np.asarray(z["y"], dtype=np.float64) if "y" in z else None
                w = np.asarray(z["w"], dtype=np.float64) if "w" in z else None
            return X, y, w
        if path.endswith(".npy"):
            return np.asarray(np.load(path), dtype=np.float64), None, None
        if path.endswith(".parquet"):
            import pandas as pd

            df = pd.read_parquet(path)
            y = df.pop("label").to_numpy(np.float64) if "label" in df else None
            w = df.pop("weight").to_numpy(np.float64) if "weight" in df else None
            return df.to_numpy(np.float64), y, w
        raise ValueError(f"unsupported shard format: {path}")

    @staticmethod
    def load_rows(
        path: str, lo: int, hi: int
    ) -> Tuple[np.ndarray, Optional[np.ndarray], Optional[np.ndarray]]:
        """Decode only rows ``[lo, hi)`` of a shard — the memory-bounded
        load. ``.npy`` slices a read-only memmap (only the touched pages
        become resident); ``.npz`` seeks within the zip member past the
        skipped rows and reads exactly the requested range (``np.savez``
        stores members uncompressed, so the seek is a file seek, not a
        decompress-and-discard); parquet has no streamable row access and
        falls back to a full decode plus slice."""
        check_record(path)
        _verify_shard(path)
        lo, hi = int(lo), int(hi)
        if path.endswith(".npy"):
            mm = np.load(path, mmap_mode="r")
            return np.asarray(mm[lo:hi], dtype=np.float64), None, None
        if path.endswith(".npz"):
            import zipfile

            def member_rows(z, name):
                with z.open(name) as fh:
                    version = np.lib.format.read_magic(fh)
                    if version == (1, 0):
                        shape, fortran, dtype = \
                            np.lib.format.read_array_header_1_0(fh)
                    else:
                        shape, fortran, dtype = \
                            np.lib.format.read_array_header_2_0(fh)
                    if fortran:
                        # column-major rows aren't contiguous in the
                        # stream; decode the member, then slice
                        data = np.frombuffer(fh.read(), dtype=dtype)
                        return data.reshape(shape, order="F")[lo:hi] \
                            .astype(np.float64)
                    count = hi - lo
                    row_elems = 1
                    for d in shape[1:]:
                        row_elems *= int(d)
                    rowbytes = row_elems * dtype.itemsize
                    fh.seek(lo * rowbytes, 1)
                    buf = fh.read(count * rowbytes)
                    arr = np.frombuffer(buf, dtype=dtype).reshape(
                        (count,) + tuple(shape[1:])
                    )
                    return arr.astype(np.float64)

            with zipfile.ZipFile(path) as z:
                names = set(z.namelist())
                X = member_rows(z, "X.npy")
                y = member_rows(z, "y.npy") if "y.npy" in names else None
                w = member_rows(z, "w.npy") if "w.npy" in names else None
            return X, y, w
        X, y, w = ShardedDataset._load(path)
        return (
            X[lo:hi],
            y[lo:hi] if y is not None else None,
            w[lo:hi] if w is not None else None,
        )

    def iter_shards(self) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray], Optional[np.ndarray]]]:
        # scan first: under permissive/dropmalformed the scan prunes
        # self.paths to the survivor list, so iteration (and everything
        # built on it — sampling, binning) never touches a corrupt shard
        self._scan()
        for p in self.paths:
            yield self._load(p)

    @staticmethod
    def _shard_info(path: str) -> ShardInfo:
        """Shape/key metadata WITHOUT decoding the float data — .npy/.npz
        headers are read directly so the scan pass is O(shards), not
        O(bytes) (at the 1B-row design point a decode pass costs hours)."""
        if path.endswith(".npy"):
            with open(path, "rb") as fh:
                shape = _npy_header_shape(fh)
            return ShardInfo(path, shape[0], shape[1])
        if path.endswith(".npz"):
            import zipfile

            with zipfile.ZipFile(path) as z:
                names = set(z.namelist())
                with z.open("X.npy") as fh:
                    shape = _npy_header_shape(fh)
            return ShardInfo(
                path, shape[0], shape[1],
                has_y="y.npy" in names, has_w="w.npy" in names,
            )
        X, y, w = ShardedDataset._load(path)  # parquet etc: full decode (once)
        return ShardInfo(
            path, len(X), X.shape[1], has_y=y is not None, has_w=w is not None
        )

    def _scan(self) -> None:
        if self._infos is not None:
            return
        infos = []
        survivors = []
        bad: List[CorruptRecord] = []
        f = None
        for p in self.paths:
            if self.mode != FAILFAST:
                # Eager verification: surface torn files / stale CRC
                # sidecars NOW, so every downstream sizing decision
                # (row offsets, memmap extent, samples) is computed over
                # the final survivor list and row order is deterministic.
                try:
                    check_record(p)
                    _verify_shard(p)
                    info = self._shard_info(p)
                except _CORRUPT_ERRORS as e:
                    bad.append(CorruptRecord.from_error(p, e))
                    continue
            else:
                info = self._shard_info(p)
            if f is None:
                f = info.num_features
            elif info.num_features != f:
                if self.mode != FAILFAST:
                    bad.append(CorruptRecord(
                        source=p, index=-1, reason="feature-count-mismatch",
                        detail=f"has {info.num_features} features, expected {f}",
                    ))
                    continue
                raise ValueError(
                    f"shard {p} has {info.num_features} features, expected {f}"
                )
            survivors.append(p)
            infos.append(info)
        if bad:
            self.quarantined = bad
            self.paths = survivors
            if not survivors:
                raise BadRecordsError(
                    f"all {len(bad)} shard(s) are corrupt", records=bad,
                )
            if self.mode == PERMISSIVE and self.bad_records_path:
                from mmlspark_tpu.dataguard.dlq import DeadLetterStore

                DeadLetterStore(
                    self.bad_records_path, name="sharded"
                ).letter(bad)
        # weights must be all-or-none: a missing 'w' in one shard silently
        # training unweighted would be a data-loss bug, not a default
        ws = {i.has_w for i in infos}
        if len(ws) > 1:
            raise ValueError(
                "inconsistent shards: some carry weights ('w') and some do not"
            )
        self._infos = infos
        self._num_features = int(f)

    @property
    def num_rows(self) -> int:
        self._scan()
        return sum(i.num_rows for i in self._infos)

    @property
    def num_features(self) -> int:
        self._scan()
        return self._num_features

    # -- streaming binning ---------------------------------------------------

    def sample_rows(self, per_shard: int, seed: int = 0) -> np.ndarray:
        """Bounded per-shard row sample for quantile fitting."""
        rng = np.random.default_rng(seed)
        chunks = []
        for X, _, _ in self.iter_shards():
            if len(X) > per_shard:
                idx = rng.choice(len(X), size=per_shard, replace=False)
                chunks.append(X[idx])
            else:
                chunks.append(X)
        return np.concatenate(chunks, axis=0)

    def fit_mapper(
        self, max_bin: int = 255, sample_per_shard: int = 50_000, seed: int = 0
    ) -> BinMapper:
        return fit_bin_mapper(
            self.sample_rows(sample_per_shard, seed), max_bin=max_bin
        )

    def bin_to_memmap(
        self,
        mapper: BinMapper,
        out_path: Optional[str] = None,
        policy=None,
        metrics=None,
        rows_per_task: Optional[int] = None,
    ) -> Tuple[np.memmap, np.ndarray, Optional[np.ndarray]]:
        """Stream every shard through ``apply_bins`` into an on-disk uint8
        matrix. Returns (bins memmap (N, F) uint8, y (N,), w or None) —
        labels/weights are small (8 bytes/row) and stay in RAM.

        With a :class:`~mmlspark_tpu.runtime.SchedulerPolicy` (explicit or
        ambient via ``runtime.policy()``), each shard becomes one task on
        the fault-tolerant scheduler: shards bin concurrently into their
        disjoint memmap slices, a dead executor's shard is retried, and the
        shard file itself is the lineage source (a lost partition re-reads
        from disk). Tasks decode only their own row range
        (:meth:`load_rows`), so worker RSS is bounded by the task's rows,
        not the shard file. ``rows_per_task`` caps rows per task
        explicitly; when None, whole-shard tasks are used unless the
        resource watchdog reports ambient memory pressure, in which case
        shards auto-split (halved ranges at WARN, quartered at CRITICAL).
        Output is bit-identical to the sequential pass — every task writes
        only its own row range."""
        self._scan()
        n, f = self.num_rows, self.num_features
        # fail fast on unlabeled data — BEFORE the (potentially hours-long)
        # streaming-bin pass; _scan read the keys from the shard headers
        if not all(i.has_y for i in self._infos):
            raise ValueError("shards carry no labels ('y'); cannot train")
        have_w = all(i.has_w for i in self._infos)
        if out_path is None:
            fd, out_path = tempfile.mkstemp(suffix=".bins.u8")
            os.close(fd)
        bins = np.memmap(out_path, dtype=np.uint8, mode="w+", shape=(n, f))
        y_all = np.empty(n, dtype=np.float64)
        w_all = np.empty(n, dtype=np.float64) if have_w else None

        from mmlspark_tpu import runtime

        pol = policy or runtime.current_policy()
        if pol is None:
            lo = 0
            for X, y, w in self.iter_shards():
                hi = lo + len(X)
                bins[lo:hi] = apply_bins(X, mapper)
                y_all[lo:hi] = y
                if have_w:
                    w_all[lo:hi] = w
                lo = hi
        else:
            offsets = np.cumsum([0] + [i.num_rows for i in self._infos])
            split = rows_per_task
            if split is None:
                # first consumer of the resource watchdog's host-memory
                # signal: under ambient pressure, cap the rows a single
                # task may decode so worker RSS shrinks with the level
                from mmlspark_tpu.runtime.pressure import (
                    PressureLevel, current_pressure_level,
                )

                level = current_pressure_level("memory")
                if level >= PressureLevel.WARN:
                    biggest = max(i.num_rows for i in self._infos)
                    div = 4 if level >= PressureLevel.CRITICAL else 2
                    split = max(1, -(-biggest // div))
            parts = []  # (shard index, row lo, row hi) within the shard
            for si, info in enumerate(self._infos):
                step = split if split is not None else max(info.num_rows, 1)
                for plo in range(0, info.num_rows, step):
                    parts.append((si, plo, min(plo + step, info.num_rows)))
            lineage = runtime.Lineage()
            tasks = [
                lineage.record(
                    pi,
                    (lambda si=si, plo=plo, phi=phi, p=self.paths[si]:
                        (si, plo, phi) + self.load_rows(p, plo, phi)),
                    describe=f"{self.paths[si]}[{plo}:{phi}]",
                )
                for pi, (si, plo, phi) in enumerate(parts)
            ]

            def bin_part(payload):
                si, plo, phi, X, y, w = payload
                lo = int(offsets[si]) + int(plo)
                hi = int(offsets[si]) + int(phi)
                bins[lo:hi] = apply_bins(X, mapper)
                y_all[lo:hi] = y
                if have_w:
                    w_all[lo:hi] = w
                return hi - lo

            runtime.run_partitioned(
                bin_part, tasks, pol, lineage=lineage, metrics=metrics
            )
        bins.flush()
        return bins, y_all, w_all


def fit_gbdt_sharded(
    estimator,
    dataset: ShardedDataset,
    mesh="auto",
    sample_per_shard: int = 50_000,
    bins_path: Optional[str] = None,
):
    """Out-of-core GBDT fit: stream-bin the dataset, then run the normal
    mesh training loop over the uint8 memmap (device upload streams from
    disk; the float matrix never materializes). ``estimator`` is any
    LightGBM-style learner; returns its fitted model. ``mesh="auto"``
    honors the estimator's parallelism/numTasks params the way ``fit``
    does; pass an explicit mesh or None to override."""
    from mmlspark_tpu.lightgbm.train import train

    if mesh == "auto":
        mesh = estimator._select_mesh()
    opts = estimator._make_options(num_class=1)
    mapper = dataset.fit_mapper(
        max_bin=opts.max_bin, sample_per_shard=sample_per_shard,
        seed=estimator.getSeed(),
    )
    bins, y, w = dataset.bin_to_memmap(mapper, out_path=bins_path)
    num_class = estimator._num_classes(y)
    if num_class != 1:
        opts = estimator._make_options(num_class=num_class)
    result = train(
        bins, y, opts, w=w, mapper=mapper, mesh=mesh,
        feature_names=[f"f{i}" for i in range(dataset.num_features)],
    )
    model = estimator._make_model(result)
    model.parent = estimator
    return model
