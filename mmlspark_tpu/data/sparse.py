"""Padded sparse-feature batches — the TPU sparse format.

TPU kernels want static shapes, so a batch of hashed sparse rows is stored as
two dense (N, K) arrays — feature indices (padded with 0) and values (padded
with 0.0) — where K is the max active features per row. Zero-valued padding
is exact for linear models: gathers/scatters on index 0 with value 0
contribute nothing. This replaces JVM SparseVector columns
(``vw/VowpalWabbitFeaturizer.scala`` output).
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class SparseBatch:
    indices: np.ndarray  # (N, K) int32
    values: np.ndarray  # (N, K) float32
    dim: int  # feature-space size (1 << num_bits)

    @property
    def num_rows(self) -> int:
        return self.indices.shape[0]

    @property
    def max_active(self) -> int:
        return self.indices.shape[1]

    def to_dense(self) -> np.ndarray:
        out = np.zeros((self.num_rows, self.dim), dtype=np.float32)
        rows = np.repeat(np.arange(self.num_rows), self.max_active)
        np.add.at(out, (rows, self.indices.reshape(-1)), self.values.reshape(-1))
        return out

    @staticmethod
    def from_csr(
        indices: np.ndarray,
        values: np.ndarray,
        indptr: np.ndarray,
        dim: int,
        pad_to: int = 0,
    ) -> "SparseBatch":
        """Pad CSR arrays straight into the (N, K) device layout with one
        scatter — the fast path ``from_lists`` assembly reduces to when rows
        arrive as flat (indices, values, indptr) instead of n Python lists.
        Assumes duplicate indices are already combined (see
        :func:`combine_csr`); K matches ``from_lists`` (max row length,
        floor 1, or ``pad_to``)."""
        indptr = np.asarray(indptr, dtype=np.int64)
        counts = np.diff(indptr)
        n = len(counts)
        k = int(max(counts.max() if n else 0, 1, pad_to))
        ind2d = np.zeros((n, k), dtype=np.int32)
        val2d = np.zeros((n, k), dtype=np.float32)
        nnz = int(indptr[-1]) if n else 0
        if nnz:
            row_ids = np.repeat(np.arange(n, dtype=np.int64), counts)
            within = np.arange(nnz, dtype=np.int64) - indptr[row_ids]
            ind2d[row_ids, within] = indices[:nnz]
            val2d[row_ids, within] = values[:nnz]
        return SparseBatch(indices=ind2d, values=val2d, dim=dim)


@dataclasses.dataclass
class CSRMatrix:
    """Host-side CSR matrix for GBDT ingest — the ``LGBM_DatasetCreateFromCSRSpark``
    analogue (reference ``lightgbm/LightGBMUtils.scala:246-266``).

    Implicit entries are 0.0 (not missing); explicit NaN marks missing, same
    as the dense path. The TPU design point: sparsity lives only on the host
    ingest side — binning maps a CSR column-by-column straight to the dense
    row-major uint8 bin matrix the chip wants (max_bin<=255 means the binned
    form is 8x smaller than dense float64, so densifying *bins* is the
    memory-sane layout even for fairly sparse data; truly high-dimensional
    sparse text goes through the VW path's SparseBatch instead)."""

    data: np.ndarray  # (nnz,) float64
    indices: np.ndarray  # (nnz,) int32 column index per entry
    indptr: np.ndarray  # (N+1,) int64 row pointers
    shape: Tuple[int, int]

    def __post_init__(self):
        self.data = np.asarray(self.data, dtype=np.float64)
        self.indices = np.asarray(self.indices, dtype=np.int32)
        self.indptr = np.asarray(self.indptr, dtype=np.int64)

    @property
    def num_rows(self) -> int:
        return self.shape[0]

    @property
    def num_features(self) -> int:
        return self.shape[1]

    @property
    def nnz(self) -> int:
        return len(self.data)

    @staticmethod
    def from_scipy(m) -> "CSRMatrix":
        csr = m.tocsr() if hasattr(m, "tocsr") else m
        return CSRMatrix(
            data=np.asarray(csr.data, dtype=np.float64),
            indices=np.asarray(csr.indices, dtype=np.int32),
            indptr=np.asarray(csr.indptr, dtype=np.int64),
            shape=tuple(csr.shape),
        )

    @staticmethod
    def from_rows(rows: Sequence[Tuple[np.ndarray, np.ndarray]], num_features: int = 0) -> "CSRMatrix":
        """Build from per-row (indices, values) pairs — the object-column
        convention shared with :func:`column_to_batch`."""
        idx_lists = [np.asarray(r[0], dtype=np.int64) for r in rows]
        val_lists = [np.asarray(r[1], dtype=np.float64) for r in rows]
        lens = np.array([len(i) for i in idx_lists], dtype=np.int64)
        indptr = np.zeros(len(rows) + 1, dtype=np.int64)
        np.cumsum(lens, out=indptr[1:])
        indices = (
            np.concatenate(idx_lists) if idx_lists else np.zeros(0, dtype=np.int64)
        )
        data = np.concatenate(val_lists) if val_lists else np.zeros(0, dtype=np.float64)
        max_idx = int(indices.max()) if len(indices) else -1
        if num_features and max_idx >= num_features:
            raise ValueError(
                f"sparse feature index {max_idx} out of range for "
                f"num_features={num_features}"
            )
        f = int(num_features or max_idx + 1)
        return CSRMatrix(data=data, indices=indices, indptr=indptr, shape=(len(rows), f))

    @staticmethod
    def from_dense(dense: np.ndarray) -> "CSRMatrix":
        dense = np.asarray(dense, dtype=np.float64)
        n, f = dense.shape
        mask = (dense != 0) | np.isnan(dense)
        indptr = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(mask.sum(axis=1), out=indptr[1:])
        rows, cols = np.nonzero(mask)
        return CSRMatrix(
            data=dense[rows, cols], indices=cols, indptr=indptr, shape=(n, f)
        )

    def row_slice(self, lo: int, hi: int) -> "CSRMatrix":
        a, b = self.indptr[lo], self.indptr[hi]
        return CSRMatrix(
            data=self.data[a:b],
            indices=self.indices[a:b],
            indptr=self.indptr[lo : hi + 1] - a,
            shape=(hi - lo, self.shape[1]),
        )

    def take_rows(self, idx: np.ndarray) -> "CSRMatrix":
        idx = np.asarray(idx)
        if idx.dtype == bool:
            idx = np.nonzero(idx)[0]
        rows = [
            (
                self.indices[self.indptr[i] : self.indptr[i + 1]],
                self.data[self.indptr[i] : self.indptr[i + 1]],
            )
            for i in idx
        ]
        return CSRMatrix.from_rows(rows, num_features=self.shape[1])

    def to_csc(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Column-major view: (col_indptr (F+1,), row_ids (nnz,), values (nnz,)).
        One stable argsort over column ids — the whole 'CSC conversion'."""
        order = np.argsort(self.indices, kind="stable")
        col_sorted = self.indices[order]
        row_ids = np.repeat(
            np.arange(self.num_rows, dtype=np.int64), np.diff(self.indptr)
        )[order]
        values = self.data[order]
        col_indptr = np.zeros(self.num_features + 1, dtype=np.int64)
        np.cumsum(np.bincount(col_sorted, minlength=self.num_features), out=col_indptr[1:])
        return col_indptr, row_ids, values

    def to_dense(self, dtype=np.float64) -> np.ndarray:
        out = np.zeros(self.shape, dtype=dtype)
        rows = np.repeat(np.arange(self.num_rows), np.diff(self.indptr))
        out[rows, self.indices] = self.data
        return out


class SparseRows:
    """CSR-backed sparse column — a drop-in for the object column of per-row
    ``(indices, values)`` tuples the VW featurizer used to emit, without
    materializing n Python tuples. Three flat arrays back the whole column
    (``indices`` int32, ``values`` float32, ``indptr`` int64 row pointers),
    so consumers that understand CSR (``column_to_batch``,
    ``csr_column_to_matrix``) move batches with scatters instead of per-row
    loops, while row access (``col[i]`` -> (idx, val) views), iteration,
    masking, and fancy indexing keep the old column contract for everything
    else. Duck-types just enough of a 1-D object ndarray to live inside a
    :class:`~mmlspark_tpu.data.table.Table`."""

    dtype = np.dtype(object)
    ndim = 1

    def __init__(
        self,
        indices: np.ndarray,
        values: np.ndarray,
        indptr: np.ndarray,
        dim: int,
    ):
        self.indices = np.asarray(indices, dtype=np.int32)
        self.values = np.asarray(values, dtype=np.float32)
        self.indptr = np.asarray(indptr, dtype=np.int64)
        self.dim = int(dim)

    @property
    def shape(self) -> Tuple[int]:
        return (len(self.indptr) - 1,)

    @property
    def nnz(self) -> int:
        return int(self.indptr[-1])

    def __len__(self) -> int:
        return len(self.indptr) - 1

    def __getitem__(self, key):
        if isinstance(key, (int, np.integer)):
            i = int(key)
            n = len(self)
            if i < 0:
                i += n
            if not 0 <= i < n:
                raise IndexError(f"row {key} out of range for {n} rows")
            a, b = self.indptr[i], self.indptr[i + 1]
            return (self.indices[a:b], self.values[a:b])
        if isinstance(key, slice):
            start, stop, step = key.indices(len(self))
            if step == 1:
                stop = max(stop, start)
                a = self.indptr[start]
                return SparseRows(
                    self.indices[a : self.indptr[stop]],
                    self.values[a : self.indptr[stop]],
                    self.indptr[start : stop + 1] - a,
                    self.dim,
                )
            return self.take(np.arange(start, stop, step))
        key = np.asarray(key)
        if key.dtype == bool:
            key = np.nonzero(key)[0]
        return self.take(key)

    def take(self, rows: np.ndarray) -> "SparseRows":
        rows = np.asarray(rows, dtype=np.int64)
        counts = np.diff(self.indptr)[rows]
        new_indptr = np.zeros(len(rows) + 1, dtype=np.int64)
        np.cumsum(counts, out=new_indptr[1:])
        total = int(new_indptr[-1])
        # source position of each gathered entry: row start + offset-in-row
        pos = (
            np.repeat(self.indptr[rows], counts)
            + np.arange(total, dtype=np.int64)
            - np.repeat(new_indptr[:-1], counts)
        )
        return SparseRows(self.indices[pos], self.values[pos], new_indptr, self.dim)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def copy(self) -> "SparseRows":
        return SparseRows(
            self.indices.copy(), self.values.copy(), self.indptr.copy(), self.dim
        )

    def to_object_column(self) -> np.ndarray:
        """Materialize the legacy object column of (indices, values) tuples."""
        out = np.empty(len(self), dtype=object)
        for i in range(len(self)):
            out[i] = self[i]
        return out

    @staticmethod
    def concat(parts: Sequence["SparseRows"]) -> "SparseRows":
        dim = max(p.dim for p in parts)
        indptrs = [parts[0].indptr]
        for p in parts[1:]:
            indptrs.append(p.indptr[1:] + (indptrs[-1][-1] - p.indptr[0]))
        return SparseRows(
            np.concatenate([p.indices for p in parts]),
            np.concatenate([p.values for p in parts]),
            np.concatenate(indptrs),
            dim,
        )

    def __repr__(self) -> str:
        return f"SparseRows[{len(self)} rows, nnz={self.nnz}, dim={self.dim}]"


def csr_column_to_matrix(column: np.ndarray, num_features: int = 0) -> CSRMatrix:
    """Interpret an object column of (indices, values) tuples as a CSRMatrix.
    :class:`SparseRows` columns convert with three array casts — no row loop."""
    if isinstance(column, SparseRows):
        f = int(num_features or column.dim)
        if column.nnz and int(column.indices.max()) >= f:
            raise ValueError(
                f"sparse feature index {int(column.indices.max())} out of "
                f"range for num_features={f}"
            )
        return CSRMatrix(
            data=column.values,
            indices=column.indices,
            indptr=column.indptr,
            shape=(len(column), f),
        )
    return CSRMatrix.from_rows(list(column), num_features=num_features)


def is_sparse_column(column: np.ndarray) -> bool:
    """True when a column holds per-row (indices, values) sparse rows."""
    if isinstance(column, SparseRows):
        return True
    if column.dtype != object or len(column) == 0:
        return False
    head = column[0]
    return (
        isinstance(head, tuple)
        and len(head) == 2
        and np.asarray(head[0]).ndim == 1
        and np.asarray(head[1]).ndim == 1
        and np.issubdtype(np.asarray(head[0]).dtype, np.integer)
    )


def from_lists(
    index_lists: Sequence[np.ndarray],
    value_lists: Sequence[np.ndarray],
    dim: int,
    sum_collisions: bool = True,
    pad_to: int = 0,
) -> SparseBatch:
    """Assemble per-row (indices, values) into a padded batch, combining
    duplicate indices within a row (``sumCollisions`` semantics)."""
    combined: List[Tuple[np.ndarray, np.ndarray]] = []
    max_k = 1
    for idx, val in zip(index_lists, value_lists):
        idx = np.asarray(idx, dtype=np.int64)
        val = np.asarray(val, dtype=np.float32)
        if len(idx):
            uniq, inv = np.unique(idx, return_inverse=True)
            if len(uniq) < len(idx):
                if sum_collisions:
                    summed = np.zeros(len(uniq), dtype=np.float32)
                    np.add.at(summed, inv, val)
                    idx, val = uniq, summed
                else:
                    # keep first occurrence per index
                    first = np.full(len(uniq), -1, dtype=np.int64)
                    for pos, u in enumerate(inv):
                        if first[u] < 0:
                            first[u] = pos
                    idx, val = uniq, val[first]
        combined.append((idx, val))
        max_k = max(max_k, len(idx))
    k = max(max_k, pad_to)
    n = len(combined)
    indices = np.zeros((n, k), dtype=np.int32)
    values = np.zeros((n, k), dtype=np.float32)
    for i, (idx, val) in enumerate(combined):
        indices[i, : len(idx)] = idx
        values[i, : len(val)] = val
    return SparseBatch(indices=indices, values=values, dim=dim)


def _combine_ones_padded(
    indices: np.ndarray,
    values: np.ndarray,
    indptr: np.ndarray,
    counts: np.ndarray,
    K: int,
    sum_collisions: bool,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """combine_csr fast path for all-ones values: rows scatter into a padded
    (n, K+1) int32 matrix whose rows sort independently (SIMD sorting
    networks on short rows beat a global radix sort by ~15x), duplicates
    collapse to runs, and a group's summed value is just its run length.
    The extra sentinel column guarantees every row ends with a padding run,
    so each valid run's extent is bounded by the next boundary in the SAME
    row. No zero-trim pass: combined values are always >= 1."""
    n = len(counts)
    nnz = int(indptr[-1])
    sent = np.int32(2**31 - 1)
    W = K + 1
    m = np.full((n, W), sent, dtype=np.int32)
    if bool((counts == K).all()):
        m[:, :K] = indices.astype(np.int32, copy=False).reshape(n, K)
    else:
        row_ids = np.repeat(np.arange(n, dtype=np.int64), counts)
        within = np.arange(nnz, dtype=np.int64) - np.repeat(indptr[:-1], counts)
        m[row_ids, within] = indices
    ms = np.sort(m, axis=1)  # sentinels sort to the tail of each row
    b2 = np.empty((n, W), dtype=bool)
    b2[:, 0] = True
    np.not_equal(ms[:, 1:], ms[:, :-1], out=b2[:, 1:])
    # each row contributes exactly one padding run (the sentinel column
    # guarantees it), so distinct indices per row = boundaries - 1
    ucounts = np.count_nonzero(b2, axis=1) - 1
    has_dup = ucounts < counts
    if not has_dup.any():
        return indices.astype(np.int32, copy=False), values, indptr
    # Duplicate-free rows have ucounts == counts, so the kept run stream IS
    # the output — no per-group destination scatter at all. Values are 1 for
    # singleton runs, so only indices of duplicate-free rows need an
    # original-order overwrite afterwards.
    q = np.flatnonzero(b2.ravel())  # run starts, row-major => sorted per row
    vals_q = ms.ravel()[q]
    keep_q = vals_q != sent  # drop each row's padding run (and empty rows)
    out_idx = vals_q[keep_q]
    if sum_collisions:
        runs = np.empty(len(q), dtype=np.int64)
        np.subtract(q[1:], q[:-1], out=runs[:-1])
        runs[-1] = n * W - q[-1]  # last boundary is always a padding run
        out_val = runs[keep_q].astype(np.float32)
    else:
        out_val = np.ones(len(out_idx), dtype=np.float32)  # first of a 1 is 1
    out_indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(ucounts, out=out_indptr[1:])
    # duplicate-free rows: restore original entry order; work is O(their nnz)
    nd_rows = np.flatnonzero(~has_dup)
    c_nd = counts[nd_rows]
    tot_nd = int(c_nd.sum())
    if tot_nd:
        seg = np.arange(tot_nd, dtype=np.int64) - np.repeat(
            np.cumsum(c_nd) - c_nd, c_nd
        )
        src = np.repeat(indptr[nd_rows], c_nd) + seg
        dst = np.repeat(out_indptr[nd_rows], c_nd) + seg
        out_idx[dst] = indices[src]
    return out_idx, out_val, out_indptr


def combine_csr(
    indices: np.ndarray,
    values: np.ndarray,
    indptr: np.ndarray,
    sum_collisions: bool = True,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Row-wise duplicate-index combine + zero-value trim over flat CSR
    arrays — the vectorized equivalent of ``from_lists`` collision handling
    followed by ``batch_to_column`` trimming, bit-exact with both:

    - a row WITHOUT duplicate indices keeps its original entry order
      (``from_lists`` only touches a row when ``np.unique`` shrinks it);
    - a row WITH duplicates becomes sorted-unique, values summed in float32
      in original occurrence order (``sumCollisions=True``) or taken from
      the first occurrence (``False``);
    - entries whose combined value is exactly 0 are dropped (the padded
      batch cannot distinguish them from padding).

    Returns combined ``(indices int32, values float32, indptr int64)``.
    """
    indices = np.asarray(indices)
    if indices.dtype.kind != "i":
        indices = indices.astype(np.int64)
    values = np.asarray(values, dtype=np.float32)
    indptr = np.asarray(indptr, dtype=np.int64)
    n = len(indptr) - 1
    nnz = int(indptr[-1]) if n else 0
    if nnz == 0:
        return (
            np.zeros(0, dtype=np.int32),
            np.zeros(0, dtype=np.float32),
            np.zeros(n + 1, dtype=np.int64),
        )
    counts = np.diff(indptr)
    all_ones = bool((values == np.float32(1.0)).all())
    K = int(counts.max())
    if (
        all_ones
        and int(indices.max()) < 2**31 - 1
        and K < (1 << 24)  # run lengths stay exact in f32
        and n * (K + 1) <= 2 * nnz + 4096  # padding waste bounded
    ):
        # All-ones columns (hashed text, the hot path): group values are just
        # run lengths, so the expensive global (row, index) radix sort
        # collapses to a per-row np.sort over a padded int32 matrix — an
        # order of magnitude cheaper on short rows.
        return _combine_ones_padded(indices, values, indptr, counts, K, sum_collisions)
    # One stable sort over (row, index) keys groups duplicates per row while
    # preserving original occurrence order inside each group. Integer keys
    # take numpy's radix path, so this is bandwidth- not comparison-bound.
    span = int(indices.max()) + 1
    key = np.repeat(np.arange(n, dtype=np.int64), counts) * span
    key += indices
    order = np.argsort(key, kind="stable")
    sk = key[order]
    newgrp = np.ones(nnz, dtype=bool)
    np.not_equal(sk[1:], sk[:-1], out=newgrp[1:])
    gstart = np.flatnonzero(newgrp)  # group start positions, sorted order
    n_groups = len(gstart)
    sk_g = sk[gstart]
    grp_row = sk_g // span
    idx_g = sk_g - grp_row * span
    ucounts = np.bincount(grp_row, minlength=n)
    has_dup = ucounts < counts
    if not has_dup.any():
        # fast path: nothing to combine, just trim exact zeros
        out_idx, out_val, out_counts = indices, values, counts
    else:
        # Duplicate-free rows have ucounts == counts, so the group stream IS
        # the output; they just get an original-order overwrite afterwards
        # (their groups are singletons, but sorted, not occurrence-ordered).
        out_counts = ucounts
        out_indptr = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(ucounts, out=out_indptr[1:])
        out_idx = idx_g
        if not sum_collisions:
            out_val = values[order[gstart]]  # stable sort => first occurrence
        elif all_ones:
            # each group sums to its size, exact in f32
            out_val = np.diff(np.append(gstart, nnz)).astype(np.float32)
        else:
            gid = np.cumsum(newgrp) - 1
            out_val = np.zeros(n_groups, dtype=np.float32)
            np.add.at(out_val, gid, values[order])  # f32 accumulate, like from_lists
        # duplicate-free rows: restore original entry order, O(their nnz)
        nd_rows = np.flatnonzero(~has_dup)
        c_nd = counts[nd_rows]
        tot_nd = int(c_nd.sum())
        if tot_nd:
            seg = np.arange(tot_nd, dtype=np.int64) - np.repeat(
                np.cumsum(c_nd) - c_nd, c_nd
            )
            src = np.repeat(indptr[nd_rows], c_nd) + seg
            dst = np.repeat(out_indptr[nd_rows], c_nd) + seg
            out_idx[dst] = indices[src]
            out_val[dst] = values[src]
    if np.count_nonzero(out_val) == len(out_val):
        # no exact-zero values to trim
        final_indptr = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(out_counts, out=final_indptr[1:])
        return out_idx.astype(np.int32, copy=False), out_val.astype(np.float32, copy=False), final_indptr
    keep = out_val != 0
    out_row = np.repeat(np.arange(n, dtype=np.int64), out_counts)
    final_indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(np.bincount(out_row[keep], minlength=n).astype(np.int64), out=final_indptr[1:])
    return out_idx[keep].astype(np.int32, copy=False), out_val[keep].astype(np.float32, copy=False), final_indptr


def dense_to_batch(dense: np.ndarray, dim: int) -> SparseBatch:
    """View a dense (N, F) matrix as a SparseBatch whose feature j is index j.
    ``dim`` must be > F; the extra tail slots are free for e.g. a bias term."""
    dense = np.asarray(dense, dtype=np.float32)
    n, f = dense.shape
    if dim <= f:
        raise ValueError(f"dim {dim} must exceed feature count {f}")
    return SparseBatch(
        indices=np.broadcast_to(np.arange(f, dtype=np.int32), (n, f)).copy(),
        values=dense,
        dim=dim,
    )


def column_to_batch(column: np.ndarray, dim: int) -> SparseBatch:
    """Interpret a sparse column as a SparseBatch. :class:`SparseRows`
    columns (already duplicate-combined by construction) pad with one
    scatter; legacy object columns of (indices, values) tuples fall back to
    the per-row ``from_lists`` assembly."""
    if isinstance(column, SparseRows):
        return SparseBatch.from_csr(
            column.indices, column.values, column.indptr, dim
        )
    idx_lists = [np.asarray(x[0]) for x in column]
    val_lists = [np.asarray(x[1]) for x in column]
    return from_lists(idx_lists, val_lists, dim)


def batch_to_column(batch: SparseBatch) -> np.ndarray:
    """Store a SparseBatch as an object column of (indices, values) tuples,
    trimming per-row padding."""
    out = np.empty(batch.num_rows, dtype=object)
    for i in range(batch.num_rows):
        mask = batch.values[i] != 0
        # keep index-0 entries only if they carry value; padding has value 0
        out[i] = (batch.indices[i][mask].copy(), batch.values[i][mask].copy())
    return out
