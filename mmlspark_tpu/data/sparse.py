"""Padded sparse-feature batches — the TPU sparse format.

TPU kernels want static shapes, so a batch of hashed sparse rows is stored as
two dense (N, K) arrays — feature indices (padded with 0) and values (padded
with 0.0) — where K is the max active features per row. Zero-valued padding
is exact for linear models: gathers/scatters on index 0 with value 0
contribute nothing. This replaces JVM SparseVector columns
(``vw/VowpalWabbitFeaturizer.scala`` output).
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class SparseBatch:
    indices: np.ndarray  # (N, K) int32
    values: np.ndarray  # (N, K) float32
    dim: int  # feature-space size (1 << num_bits)

    @property
    def num_rows(self) -> int:
        return self.indices.shape[0]

    @property
    def max_active(self) -> int:
        return self.indices.shape[1]

    def to_dense(self) -> np.ndarray:
        out = np.zeros((self.num_rows, self.dim), dtype=np.float32)
        rows = np.repeat(np.arange(self.num_rows), self.max_active)
        np.add.at(out, (rows, self.indices.reshape(-1)), self.values.reshape(-1))
        return out


def from_lists(
    index_lists: Sequence[np.ndarray],
    value_lists: Sequence[np.ndarray],
    dim: int,
    sum_collisions: bool = True,
    pad_to: int = 0,
) -> SparseBatch:
    """Assemble per-row (indices, values) into a padded batch, combining
    duplicate indices within a row (``sumCollisions`` semantics)."""
    combined: List[Tuple[np.ndarray, np.ndarray]] = []
    max_k = 1
    for idx, val in zip(index_lists, value_lists):
        idx = np.asarray(idx, dtype=np.int64)
        val = np.asarray(val, dtype=np.float32)
        if len(idx):
            uniq, inv = np.unique(idx, return_inverse=True)
            if len(uniq) < len(idx):
                if sum_collisions:
                    summed = np.zeros(len(uniq), dtype=np.float32)
                    np.add.at(summed, inv, val)
                    idx, val = uniq, summed
                else:
                    # keep first occurrence per index
                    first = np.full(len(uniq), -1, dtype=np.int64)
                    for pos, u in enumerate(inv):
                        if first[u] < 0:
                            first[u] = pos
                    idx, val = uniq, val[first]
        combined.append((idx, val))
        max_k = max(max_k, len(idx))
    k = max(max_k, pad_to)
    n = len(combined)
    indices = np.zeros((n, k), dtype=np.int32)
    values = np.zeros((n, k), dtype=np.float32)
    for i, (idx, val) in enumerate(combined):
        indices[i, : len(idx)] = idx
        values[i, : len(val)] = val
    return SparseBatch(indices=indices, values=values, dim=dim)


def dense_to_batch(dense: np.ndarray, dim: int) -> SparseBatch:
    """View a dense (N, F) matrix as a SparseBatch whose feature j is index j.
    ``dim`` must be > F; the extra tail slots are free for e.g. a bias term."""
    dense = np.asarray(dense, dtype=np.float32)
    n, f = dense.shape
    if dim <= f:
        raise ValueError(f"dim {dim} must exceed feature count {f}")
    return SparseBatch(
        indices=np.broadcast_to(np.arange(f, dtype=np.int32), (n, f)).copy(),
        values=dense,
        dim=dim,
    )


def column_to_batch(column: np.ndarray, dim: int) -> SparseBatch:
    """Interpret an object column of (indices, values) tuples as a SparseBatch."""
    idx_lists = [np.asarray(x[0]) for x in column]
    val_lists = [np.asarray(x[1]) for x in column]
    return from_lists(idx_lists, val_lists, dim)


def batch_to_column(batch: SparseBatch) -> np.ndarray:
    """Store a SparseBatch as an object column of (indices, values) tuples,
    trimming per-row padding."""
    out = np.empty(batch.num_rows, dtype=object)
    for i in range(batch.num_rows):
        mask = batch.values[i] != 0
        # keep index-0 entries only if they carry value; padding has value 0
        out[i] = (batch.indices[i][mask].copy(), batch.values[i][mask].copy())
    return out
