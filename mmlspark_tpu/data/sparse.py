"""Padded sparse-feature batches — the TPU sparse format.

TPU kernels want static shapes, so a batch of hashed sparse rows is stored as
two dense (N, K) arrays — feature indices (padded with 0) and values (padded
with 0.0) — where K is the max active features per row. Zero-valued padding
is exact for linear models: gathers/scatters on index 0 with value 0
contribute nothing. This replaces JVM SparseVector columns
(``vw/VowpalWabbitFeaturizer.scala`` output).
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class SparseBatch:
    indices: np.ndarray  # (N, K) int32
    values: np.ndarray  # (N, K) float32
    dim: int  # feature-space size (1 << num_bits)

    @property
    def num_rows(self) -> int:
        return self.indices.shape[0]

    @property
    def max_active(self) -> int:
        return self.indices.shape[1]

    def to_dense(self) -> np.ndarray:
        out = np.zeros((self.num_rows, self.dim), dtype=np.float32)
        rows = np.repeat(np.arange(self.num_rows), self.max_active)
        np.add.at(out, (rows, self.indices.reshape(-1)), self.values.reshape(-1))
        return out


@dataclasses.dataclass
class CSRMatrix:
    """Host-side CSR matrix for GBDT ingest — the ``LGBM_DatasetCreateFromCSRSpark``
    analogue (reference ``lightgbm/LightGBMUtils.scala:246-266``).

    Implicit entries are 0.0 (not missing); explicit NaN marks missing, same
    as the dense path. The TPU design point: sparsity lives only on the host
    ingest side — binning maps a CSR column-by-column straight to the dense
    row-major uint8 bin matrix the chip wants (max_bin<=255 means the binned
    form is 8x smaller than dense float64, so densifying *bins* is the
    memory-sane layout even for fairly sparse data; truly high-dimensional
    sparse text goes through the VW path's SparseBatch instead)."""

    data: np.ndarray  # (nnz,) float64
    indices: np.ndarray  # (nnz,) int32 column index per entry
    indptr: np.ndarray  # (N+1,) int64 row pointers
    shape: Tuple[int, int]

    def __post_init__(self):
        self.data = np.asarray(self.data, dtype=np.float64)
        self.indices = np.asarray(self.indices, dtype=np.int32)
        self.indptr = np.asarray(self.indptr, dtype=np.int64)

    @property
    def num_rows(self) -> int:
        return self.shape[0]

    @property
    def num_features(self) -> int:
        return self.shape[1]

    @property
    def nnz(self) -> int:
        return len(self.data)

    @staticmethod
    def from_scipy(m) -> "CSRMatrix":
        csr = m.tocsr() if hasattr(m, "tocsr") else m
        return CSRMatrix(
            data=np.asarray(csr.data, dtype=np.float64),
            indices=np.asarray(csr.indices, dtype=np.int32),
            indptr=np.asarray(csr.indptr, dtype=np.int64),
            shape=tuple(csr.shape),
        )

    @staticmethod
    def from_rows(rows: Sequence[Tuple[np.ndarray, np.ndarray]], num_features: int = 0) -> "CSRMatrix":
        """Build from per-row (indices, values) pairs — the object-column
        convention shared with :func:`column_to_batch`."""
        idx_lists = [np.asarray(r[0], dtype=np.int64) for r in rows]
        val_lists = [np.asarray(r[1], dtype=np.float64) for r in rows]
        lens = np.array([len(i) for i in idx_lists], dtype=np.int64)
        indptr = np.zeros(len(rows) + 1, dtype=np.int64)
        np.cumsum(lens, out=indptr[1:])
        indices = (
            np.concatenate(idx_lists) if idx_lists else np.zeros(0, dtype=np.int64)
        )
        data = np.concatenate(val_lists) if val_lists else np.zeros(0, dtype=np.float64)
        max_idx = int(indices.max()) if len(indices) else -1
        if num_features and max_idx >= num_features:
            raise ValueError(
                f"sparse feature index {max_idx} out of range for "
                f"num_features={num_features}"
            )
        f = int(num_features or max_idx + 1)
        return CSRMatrix(data=data, indices=indices, indptr=indptr, shape=(len(rows), f))

    @staticmethod
    def from_dense(dense: np.ndarray) -> "CSRMatrix":
        dense = np.asarray(dense, dtype=np.float64)
        n, f = dense.shape
        mask = (dense != 0) | np.isnan(dense)
        indptr = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(mask.sum(axis=1), out=indptr[1:])
        rows, cols = np.nonzero(mask)
        return CSRMatrix(
            data=dense[rows, cols], indices=cols, indptr=indptr, shape=(n, f)
        )

    def row_slice(self, lo: int, hi: int) -> "CSRMatrix":
        a, b = self.indptr[lo], self.indptr[hi]
        return CSRMatrix(
            data=self.data[a:b],
            indices=self.indices[a:b],
            indptr=self.indptr[lo : hi + 1] - a,
            shape=(hi - lo, self.shape[1]),
        )

    def take_rows(self, idx: np.ndarray) -> "CSRMatrix":
        idx = np.asarray(idx)
        if idx.dtype == bool:
            idx = np.nonzero(idx)[0]
        rows = [
            (
                self.indices[self.indptr[i] : self.indptr[i + 1]],
                self.data[self.indptr[i] : self.indptr[i + 1]],
            )
            for i in idx
        ]
        return CSRMatrix.from_rows(rows, num_features=self.shape[1])

    def to_csc(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Column-major view: (col_indptr (F+1,), row_ids (nnz,), values (nnz,)).
        One stable argsort over column ids — the whole 'CSC conversion'."""
        order = np.argsort(self.indices, kind="stable")
        col_sorted = self.indices[order]
        row_ids = np.repeat(
            np.arange(self.num_rows, dtype=np.int64), np.diff(self.indptr)
        )[order]
        values = self.data[order]
        col_indptr = np.zeros(self.num_features + 1, dtype=np.int64)
        np.cumsum(np.bincount(col_sorted, minlength=self.num_features), out=col_indptr[1:])
        return col_indptr, row_ids, values

    def to_dense(self, dtype=np.float64) -> np.ndarray:
        out = np.zeros(self.shape, dtype=dtype)
        rows = np.repeat(np.arange(self.num_rows), np.diff(self.indptr))
        out[rows, self.indices] = self.data
        return out


def csr_column_to_matrix(column: np.ndarray, num_features: int = 0) -> CSRMatrix:
    """Interpret an object column of (indices, values) tuples as a CSRMatrix."""
    return CSRMatrix.from_rows(list(column), num_features=num_features)


def is_sparse_column(column: np.ndarray) -> bool:
    """True when an object column holds per-row (indices, values) tuples."""
    if column.dtype != object or len(column) == 0:
        return False
    head = column[0]
    return (
        isinstance(head, tuple)
        and len(head) == 2
        and np.asarray(head[0]).ndim == 1
        and np.asarray(head[1]).ndim == 1
        and np.issubdtype(np.asarray(head[0]).dtype, np.integer)
    )


def from_lists(
    index_lists: Sequence[np.ndarray],
    value_lists: Sequence[np.ndarray],
    dim: int,
    sum_collisions: bool = True,
    pad_to: int = 0,
) -> SparseBatch:
    """Assemble per-row (indices, values) into a padded batch, combining
    duplicate indices within a row (``sumCollisions`` semantics)."""
    combined: List[Tuple[np.ndarray, np.ndarray]] = []
    max_k = 1
    for idx, val in zip(index_lists, value_lists):
        idx = np.asarray(idx, dtype=np.int64)
        val = np.asarray(val, dtype=np.float32)
        if len(idx):
            uniq, inv = np.unique(idx, return_inverse=True)
            if len(uniq) < len(idx):
                if sum_collisions:
                    summed = np.zeros(len(uniq), dtype=np.float32)
                    np.add.at(summed, inv, val)
                    idx, val = uniq, summed
                else:
                    # keep first occurrence per index
                    first = np.full(len(uniq), -1, dtype=np.int64)
                    for pos, u in enumerate(inv):
                        if first[u] < 0:
                            first[u] = pos
                    idx, val = uniq, val[first]
        combined.append((idx, val))
        max_k = max(max_k, len(idx))
    k = max(max_k, pad_to)
    n = len(combined)
    indices = np.zeros((n, k), dtype=np.int32)
    values = np.zeros((n, k), dtype=np.float32)
    for i, (idx, val) in enumerate(combined):
        indices[i, : len(idx)] = idx
        values[i, : len(val)] = val
    return SparseBatch(indices=indices, values=values, dim=dim)


def dense_to_batch(dense: np.ndarray, dim: int) -> SparseBatch:
    """View a dense (N, F) matrix as a SparseBatch whose feature j is index j.
    ``dim`` must be > F; the extra tail slots are free for e.g. a bias term."""
    dense = np.asarray(dense, dtype=np.float32)
    n, f = dense.shape
    if dim <= f:
        raise ValueError(f"dim {dim} must exceed feature count {f}")
    return SparseBatch(
        indices=np.broadcast_to(np.arange(f, dtype=np.int32), (n, f)).copy(),
        values=dense,
        dim=dim,
    )


def column_to_batch(column: np.ndarray, dim: int) -> SparseBatch:
    """Interpret an object column of (indices, values) tuples as a SparseBatch."""
    idx_lists = [np.asarray(x[0]) for x in column]
    val_lists = [np.asarray(x[1]) for x in column]
    return from_lists(idx_lists, val_lists, dim)


def batch_to_column(batch: SparseBatch) -> np.ndarray:
    """Store a SparseBatch as an object column of (indices, values) tuples,
    trimming per-row padding."""
    out = np.empty(batch.num_rows, dtype=object)
    for i in range(batch.num_rows):
        mask = batch.values[i] != 0
        # keep index-0 entries only if they carry value; padding has value 0
        out[i] = (batch.indices[i][mask].copy(), batch.values[i][mask].copy())
    return out
