// Host-side native library: quantile-bin assignment + MurmurHash3.
//
// SURVEY.md §2.20: the reference ships its host hot loops native (LightGBM
// dataset build via lib_lightgbm.so, VW hashing via vw-jni). The TPU build's
// on-chip compute is JAX/Pallas; THIS library is the host-side ingest
// counterpart — the operations that run on the CPU between storage and
// device upload:
//
// - apply_bins_u8: raw float64 features -> uint8 bin ids against per-feature
//   float32-snapped quantile edges. Bit-identical contract with the numpy
//   reference in mmlspark_tpu/lightgbm/binning.py::apply_bins (values and
//   edges compared as float32, searchsorted-left semantics, NaN -> bin 0,
//   clip to max_bin). OpenMP-style threading is deliberately absent: the
//   Python layer parallelizes over shards.
// - murmur3_x86_32: byte-string hashing matching ops/hashing.py::
//   murmur32_bytes (VW's feature-name hashing).
// - murmur3_ints_u32: vectorized 4-byte-block hashing matching
//   ops/hashing.py::murmur32_ints.
//
// Built by native/Makefile into libmmlspark_native.so; loaded via ctypes in
// mmlspark_tpu/native.py with a numpy fallback when absent.

#include <cmath>
#include <cstdint>
#include <cstring>

extern "C" {

// -- binning -----------------------------------------------------------------

// Order-preserving transform of a float32's bit pattern: negative floats map
// below positives and the mapping is monotone in the real-number order, so
// integer comparisons on keys agree with float comparisons on values
// (classic radix-sort float trick). NaNs are filtered before keying.
static inline uint32_t f32_order_key(float v) {
  if (v == 0.0f) return 0x80000000u;  // unify -0.0 with +0.0 (floats compare equal)
  uint32_t s;
  std::memcpy(&s, &v, 4);
  return (s & 0x80000000u) ? ~s : (s | 0x80000000u);
}

// X: row-major (n, f) float64; edges: row-major (f, e) float64 (padded with
// +inf); out: row-major (n, f) uint8.
//
// Per-element work is a 16-bit-prefix lookup table instead of a binary
// search: all float32 values sharing the top 16 bits of their order key form
// a value interval, so a 65536-entry table per feature stores that
// interval's [lo_bin, hi_bin]; most intervals land inside one bin (~8
// branchy search steps -> ~2 ops per element), the rest finish with a
// search over the narrowed edge range. Table build is f x 65536 walks of a
// shared pointer — O(f * (65536 + e)) — amortized over n rows.
static inline uint8_t bin_search_f32(const float* fj, int64_t lo, int64_t hi,
                                     float v, int32_t max_bin) {
  // searchsorted(fj, v, 'left') over [lo, hi): first index with fj[idx] >= v.
  while (lo < hi) {
    const int64_t mid = (lo + hi) / 2;
    if (fj[mid] < v) {
      lo = mid + 1;
    } else {
      hi = mid;
    }
  }
  int64_t bin = 1 + lo;
  if (bin > max_bin) bin = max_bin;
  return static_cast<uint8_t>(bin);
}

void apply_bins_u8(const double* X, int64_t n, int64_t f,
                   const double* edges, int64_t e,
                   uint8_t* out, int32_t max_bin) {
  // Snap every feature's edges to the float32 comparison grid once.
  const int64_t ne = e < 256 ? e : 256;
  float* fe = new float[f * ne];
  for (int64_t j = 0; j < f; ++j) {
    for (int64_t k = 0; k < ne; ++k) {
      fe[j * ne + k] = static_cast<float>(edges[j * e + k]);
    }
  }
  if (n < 16384) {
    // Small batches (per-partition predict/validation transforms): the
    // prefix tables cost O(f * 65536) to build — more than the direct
    // per-element binary search saves below ~16k rows.
    for (int64_t i = 0; i < n; ++i) {
      const double* xrow = X + i * f;
      uint8_t* orow = out + i * f;
      for (int64_t j = 0; j < f; ++j) {
        const float v = static_cast<float>(xrow[j]);
        orow[j] = std::isnan(v) ? 0 : bin_search_f32(fe + j * ne, 0, ne, v, max_bin);
      }
    }
    delete[] fe;
    return;
  }
  uint32_t* fk = new uint32_t[f * ne];  // order keys of the edges
  for (int64_t j = 0; j < f; ++j) {
    for (int64_t k = 0; k < ne; ++k) {
      const float ev = fe[j * ne + k];
      fk[j * ne + k] = std::isnan(ev) ? 0xFFFFFFFFu : f32_order_key(ev);
    }
  }
  // lo/hi bin index per 16-bit key prefix, per feature.
  const size_t tab_size = static_cast<size_t>(f) * 65536u;
  uint8_t* lo_tab = new uint8_t[tab_size];
  uint8_t* hi_tab = new uint8_t[tab_size];
  for (int64_t j = 0; j < f; ++j) {
    const uint32_t* kj = fk + j * ne;
    uint8_t* lj = lo_tab + j * 65536;
    uint8_t* hj = hi_tab + j * 65536;
    int64_t pos_lo = 0;  // first edge with key >= prefix<<16 (lowest value of class)
    for (int64_t p = 0; p < 65536; ++p) {
      while (pos_lo < ne && kj[pos_lo] < (static_cast<uint32_t>(p) << 16)) ++pos_lo;
      // highest value of the class is (p<<16)|0xFFFF
      int64_t pos_hi = pos_lo;
      const uint32_t top = (static_cast<uint32_t>(p) << 16) | 0xFFFFu;
      while (pos_hi < ne && kj[pos_hi] <= top) ++pos_hi;
      int64_t lo_bin = 1 + pos_lo;
      int64_t hi_bin = 1 + pos_hi;
      if (lo_bin > max_bin) lo_bin = max_bin;
      if (hi_bin > max_bin) hi_bin = max_bin;
      lj[p] = static_cast<uint8_t>(lo_bin);
      hj[p] = static_cast<uint8_t>(hi_bin);
    }
  }
  for (int64_t i = 0; i < n; ++i) {
    const double* xrow = X + i * f;
    uint8_t* orow = out + i * f;
    for (int64_t j = 0; j < f; ++j) {
      const float v = static_cast<float>(xrow[j]);
      if (std::isnan(v)) {
        orow[j] = 0;  // missing bin
        continue;
      }
      const uint32_t key = f32_order_key(v);
      const uint32_t p = key >> 16;
      const uint8_t lo_b = lo_tab[j * 65536 + p];
      const uint8_t hi_b = hi_tab[j * 65536 + p];
      if (lo_b == hi_b) {
        orow[j] = lo_b;
        continue;
      }
      // Narrowed searchsorted over the prefix class's edge range.
      int64_t hi = hi_b - 1;
      if (hi > ne) hi = ne;
      orow[j] = bin_search_f32(fe + j * ne, lo_b - 1, hi, v, max_bin);
    }
  }
  delete[] lo_tab;
  delete[] hi_tab;
  delete[] fe;
  delete[] fk;
}

// -- murmur3 -----------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

uint32_t murmur3_x86_32(const uint8_t* data, int64_t len, uint32_t seed) {
  const int64_t nblocks = len / 4;
  uint32_t h1 = seed;
  const uint32_t c1 = 0xcc9e2d51u;
  const uint32_t c2 = 0x1b873593u;

  for (int64_t i = 0; i < nblocks; ++i) {
    uint32_t k1;
    std::memcpy(&k1, data + i * 4, 4);  // little-endian hosts
    k1 *= c1;
    k1 = rotl32(k1, 15);
    k1 *= c2;
    h1 ^= k1;
    h1 = rotl32(h1, 13);
    h1 = h1 * 5 + 0xe6546b64u;
  }

  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3:
      k1 ^= static_cast<uint32_t>(tail[2]) << 16;
      [[fallthrough]];
    case 2:
      k1 ^= static_cast<uint32_t>(tail[1]) << 8;
      [[fallthrough]];
    case 1:
      k1 ^= tail[0];
      k1 *= c1;
      k1 = rotl32(k1, 15);
      k1 *= c2;
      h1 ^= k1;
  }

  h1 ^= static_cast<uint32_t>(len);
  return fmix32(h1);
}

// Array-of-strings murmur: hash `count` byte strings packed into one buffer
// (string i occupies buf[starts[i] .. starts[i]+lens[i])), with an optional
// namespace/column prefix virtually prepended to every string — the VW
// featurizer's "column-name + token" hashing without materializing count
// concatenated strings. One call per column replaces the per-token ctypes
// round-trip that dominated vw_text_bench host time.
static inline uint32_t mix_k1(uint32_t k1) {
  k1 *= 0xcc9e2d51u;
  k1 = rotl32(k1, 15);
  return k1 * 0x1b873593u;
}

static inline uint32_t mix_h1(uint32_t h1, uint32_t k1) {
  h1 ^= k1;
  h1 = rotl32(h1, 13);
  return h1 * 5 + 0xe6546b64u;
}

// murmur3_x86_32 of the concatenation a+b without copying: byte-at-a-time
// block assembly across the segment boundary (only used when the prefix
// length is not a multiple of 4).
static uint32_t murmur3_concat2(const uint8_t* a, int64_t la,
                                const uint8_t* b, int64_t lb, uint32_t seed) {
  const int64_t total = la + lb;
  const int64_t nblocks = total / 4;
  uint32_t h1 = seed;
  for (int64_t i = 0; i < nblocks; ++i) {
    uint32_t k1 = 0;
    for (int64_t j = 0; j < 4; ++j) {
      const int64_t p = i * 4 + j;
      const uint8_t byte = p < la ? a[p] : b[p - la];
      k1 |= static_cast<uint32_t>(byte) << (8 * j);
    }
    h1 = mix_h1(h1, mix_k1(k1));
  }
  uint32_t k1 = 0;
  for (int64_t p = nblocks * 4; p < total; ++p) {
    const uint8_t byte = p < la ? a[p] : b[p - la];
    k1 |= static_cast<uint32_t>(byte) << (8 * (p & 3));
  }
  if (total & 3) h1 ^= mix_k1(k1);
  h1 ^= static_cast<uint32_t>(total);
  return fmix32(h1);
}

void murmur3_strings_u32(const uint8_t* prefix, int64_t prefix_len,
                         const uint8_t* buf, const int64_t* starts,
                         const int32_t* lens, int64_t count, uint32_t seed,
                         uint32_t* out) {
  if (prefix_len % 4 == 0) {
    // Aligned prefix (including the empty one): fold its whole blocks into
    // the seed state ONCE, then each string continues block-aligned — the
    // VowpalWabbitMurmurWithPrefix trick, but for a packed batch.
    uint32_t h_pref = seed;
    for (int64_t i = 0; i < prefix_len / 4; ++i) {
      uint32_t k1;
      std::memcpy(&k1, prefix + i * 4, 4);
      h_pref = mix_h1(h_pref, mix_k1(k1));
    }
    for (int64_t s = 0; s < count; ++s) {
      const uint8_t* data = buf + starts[s];
      const int64_t len = lens[s];
      const int64_t nblocks = len / 4;
      uint32_t h1 = h_pref;
      for (int64_t i = 0; i < nblocks; ++i) {
        uint32_t k1;
        std::memcpy(&k1, data + i * 4, 4);
        h1 = mix_h1(h1, mix_k1(k1));
      }
      uint32_t k1 = 0;
      switch (len & 3) {
        case 3:
          k1 ^= static_cast<uint32_t>(data[nblocks * 4 + 2]) << 16;
          [[fallthrough]];
        case 2:
          k1 ^= static_cast<uint32_t>(data[nblocks * 4 + 1]) << 8;
          [[fallthrough]];
        case 1:
          k1 ^= data[nblocks * 4];
          h1 ^= mix_k1(k1);
      }
      h1 ^= static_cast<uint32_t>(prefix_len + len);
      out[s] = fmix32(h1);
    }
    return;
  }
  for (int64_t s = 0; s < count; ++s) {
    out[s] = murmur3_concat2(prefix, prefix_len, buf + starts[s], lens[s], seed);
  }
}

// Fused whitespace-split + murmur for string columns: one pass over the
// packed row bytes replaces the numpy splitter's ~8 full-buffer passes
// (whitespace LUT gather, shifted masks, two flatnonzero) AND the separate
// hashing call. Rows are split on the ASCII bytes str.split() treats as
// whitespace; each token hashes as prefix+token from `seed`. Rows containing
// a byte that can START a non-ASCII whitespace code point in utf-8 (0xC2,
// 0xE1, 0xE2, 0xE3) emit no tokens and set out_suspect[r]=1 — the caller
// re-splits those few rows with Python str.split for exactness. Returns the
// total token count written to out_hashes (caller allocates the worst case:
// (buf_len + n_rows) / 2 + 1 tokens).
int64_t murmur3_split_hash_u32(const uint8_t* prefix, int64_t prefix_len,
                               const uint8_t* buf, const int64_t* row_starts,
                               const int64_t* row_lens, int64_t n_rows,
                               uint32_t seed, uint32_t* out_hashes,
                               int64_t* out_counts, uint8_t* out_suspect) {
  bool ws[256] = {false};
  ws[9] = ws[10] = ws[11] = ws[12] = ws[13] = true;
  ws[28] = ws[29] = ws[30] = ws[31] = ws[32] = true;
  bool sus[256] = {false};
  sus[0xC2] = sus[0xE1] = sus[0xE2] = sus[0xE3] = true;
  const bool aligned = (prefix_len % 4) == 0;
  uint32_t h_pref = seed;
  if (aligned) {
    for (int64_t i = 0; i < prefix_len / 4; ++i) {
      uint32_t k1;
      std::memcpy(&k1, prefix + i * 4, 4);
      h_pref = mix_h1(h_pref, mix_k1(k1));
    }
  }
  int64_t t = 0;
  for (int64_t r = 0; r < n_rows; ++r) {
    const uint8_t* p = buf + row_starts[r];
    const int64_t L = row_lens[r];
    const int64_t t_row = t;
    bool flagged = false;
    int64_t i = 0;
    while (i < L) {
      while (i < L && ws[p[i]]) ++i;  // whitespace bytes are never suspect
      if (i >= L) break;
      const int64_t tok0 = i;
      while (i < L && !ws[p[i]]) {
        if (sus[p[i]]) {
          flagged = true;
          break;
        }
        ++i;
      }
      if (flagged) break;
      const int64_t len = i - tok0;
      const uint8_t* data = p + tok0;
      if (aligned) {
        const int64_t nblocks = len / 4;
        uint32_t h1 = h_pref;
        for (int64_t b = 0; b < nblocks; ++b) {
          uint32_t k1;
          std::memcpy(&k1, data + b * 4, 4);
          h1 = mix_h1(h1, mix_k1(k1));
        }
        uint32_t k1 = 0;
        switch (len & 3) {
          case 3:
            k1 ^= static_cast<uint32_t>(data[nblocks * 4 + 2]) << 16;
            [[fallthrough]];
          case 2:
            k1 ^= static_cast<uint32_t>(data[nblocks * 4 + 1]) << 8;
            [[fallthrough]];
          case 1:
            k1 ^= data[nblocks * 4];
            h1 ^= mix_k1(k1);
        }
        h1 ^= static_cast<uint32_t>(prefix_len + len);
        out_hashes[t++] = fmix32(h1);
      } else {
        out_hashes[t++] = murmur3_concat2(prefix, prefix_len, data, len, seed);
      }
    }
    if (flagged) {
      t = t_row;  // roll back this row's tokens; Python re-splits it
      out_counts[r] = 0;
      out_suspect[r] = 1;
    } else {
      out_counts[r] = t - t_row;
      out_suspect[r] = 0;
    }
  }
  return t;
}

// Hash each uint32 as one 4-byte block (VW integer-feature hashing);
// vectorized over `count` values.
void murmur3_ints_u32(const uint32_t* values, int64_t count, uint32_t seed,
                      uint32_t* out) {
  const uint32_t c1 = 0xcc9e2d51u;
  const uint32_t c2 = 0x1b873593u;
  for (int64_t i = 0; i < count; ++i) {
    uint32_t k1 = values[i] * c1;
    k1 = rotl32(k1, 15);
    k1 *= c2;
    uint32_t h1 = seed ^ k1;
    h1 = rotl32(h1, 13);
    h1 = h1 * 5 + 0xe6546b64u;
    h1 ^= 4u;  // length
    out[i] = fmix32(h1);
  }
}

}  // extern "C"
