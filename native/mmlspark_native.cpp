// Host-side native library: quantile-bin assignment + MurmurHash3.
//
// SURVEY.md §2.20: the reference ships its host hot loops native (LightGBM
// dataset build via lib_lightgbm.so, VW hashing via vw-jni). The TPU build's
// on-chip compute is JAX/Pallas; THIS library is the host-side ingest
// counterpart — the operations that run on the CPU between storage and
// device upload:
//
// - apply_bins_u8: raw float64 features -> uint8 bin ids against per-feature
//   float32-snapped quantile edges. Bit-identical contract with the numpy
//   reference in mmlspark_tpu/lightgbm/binning.py::apply_bins (values and
//   edges compared as float32, searchsorted-left semantics, NaN -> bin 0,
//   clip to max_bin). OpenMP-style threading is deliberately absent: the
//   Python layer parallelizes over shards.
// - murmur3_x86_32: byte-string hashing matching ops/hashing.py::
//   murmur32_bytes (VW's feature-name hashing).
// - murmur3_ints_u32: vectorized 4-byte-block hashing matching
//   ops/hashing.py::murmur32_ints.
//
// Built by native/Makefile into libmmlspark_native.so; loaded via ctypes in
// mmlspark_tpu/native.py with a numpy fallback when absent.

#include <cmath>
#include <cstdint>
#include <cstring>

extern "C" {

// -- binning -----------------------------------------------------------------

// X: row-major (n, f) float64; edges: row-major (f, e) float64 (padded with
// +inf); out: row-major (n, f) uint8.
void apply_bins_u8(const double* X, int64_t n, int64_t f,
                   const double* edges, int64_t e,
                   uint8_t* out, int32_t max_bin) {
  // Snap every feature's edges to the float32 comparison grid once
  // (f x 256 floats; <=256 KB for 256 features — L2-resident), then walk X
  // row-major so both X and out stream contiguously.
  const int64_t ne = e < 256 ? e : 256;
  float* fe = new float[f * ne];
  for (int64_t j = 0; j < f; ++j) {
    for (int64_t k = 0; k < ne; ++k) {
      fe[j * ne + k] = static_cast<float>(edges[j * e + k]);
    }
  }
  for (int64_t i = 0; i < n; ++i) {
    const double* xrow = X + i * f;
    uint8_t* orow = out + i * f;
    for (int64_t j = 0; j < f; ++j) {
      const float v = static_cast<float>(xrow[j]);
      if (std::isnan(v)) {
        orow[j] = 0;  // missing bin
        continue;
      }
      // searchsorted(fe_j, v, side='left'): first index with fe[idx] >= v
      const float* fj = fe + j * ne;
      int64_t lo = 0, hi = ne;
      while (lo < hi) {
        const int64_t mid = (lo + hi) / 2;
        if (fj[mid] < v) {
          lo = mid + 1;
        } else {
          hi = mid;
        }
      }
      int64_t bin = 1 + lo;
      if (bin > max_bin) bin = max_bin;
      orow[j] = static_cast<uint8_t>(bin);
    }
  }
  delete[] fe;
}

// -- murmur3 -----------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

uint32_t murmur3_x86_32(const uint8_t* data, int64_t len, uint32_t seed) {
  const int64_t nblocks = len / 4;
  uint32_t h1 = seed;
  const uint32_t c1 = 0xcc9e2d51u;
  const uint32_t c2 = 0x1b873593u;

  for (int64_t i = 0; i < nblocks; ++i) {
    uint32_t k1;
    std::memcpy(&k1, data + i * 4, 4);  // little-endian hosts
    k1 *= c1;
    k1 = rotl32(k1, 15);
    k1 *= c2;
    h1 ^= k1;
    h1 = rotl32(h1, 13);
    h1 = h1 * 5 + 0xe6546b64u;
  }

  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3:
      k1 ^= static_cast<uint32_t>(tail[2]) << 16;
      [[fallthrough]];
    case 2:
      k1 ^= static_cast<uint32_t>(tail[1]) << 8;
      [[fallthrough]];
    case 1:
      k1 ^= tail[0];
      k1 *= c1;
      k1 = rotl32(k1, 15);
      k1 *= c2;
      h1 ^= k1;
  }

  h1 ^= static_cast<uint32_t>(len);
  return fmix32(h1);
}

// Hash each uint32 as one 4-byte block (VW integer-feature hashing);
// vectorized over `count` values.
void murmur3_ints_u32(const uint32_t* values, int64_t count, uint32_t seed,
                      uint32_t* out) {
  const uint32_t c1 = 0xcc9e2d51u;
  const uint32_t c2 = 0x1b873593u;
  for (int64_t i = 0; i < count; ++i) {
    uint32_t k1 = values[i] * c1;
    k1 = rotl32(k1, 15);
    k1 *= c2;
    uint32_t h1 = seed ^ k1;
    h1 = rotl32(h1, 13);
    h1 = h1 * 5 + 0xe6546b64u;
    h1 ^= 4u;  // length
    out[i] = fmix32(h1);
  }
}

}  // extern "C"
